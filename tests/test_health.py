"""Numerical health plane (ISSUE 8): the admission gate + UpdateNack
quarantine, SDC chaos that is bit-perfect on the wire, worker reputation,
and the coordinator auto-rollback barrier.

- unit: GradientAdmission — warmup, nonfinite, norm outliers, the
  winsorized EWMA stopping the boiling-frog ramp, and the PINNED blind
  spot (norm-preserving sign flips pass — that is WHY the rollback
  watchdog exists).
- unit: ParameterServer quarantine — rejects never touch accounting or
  the WAL (the satellite regression: restore after a quarantine burst
  replays ZERO poison), every reject is explicitly nacked.
- unit: SDC rules corrupt through the reliability envelope (CRC
  re-stamped, crc_dropped stays 0) deterministically.
- unit: worker nack resync (pull + update hold), reputation revocation +
  cooldown (fake clock), the rollback watchdog + barrier (fake clock),
  ParameterServer.rollback_restore + WAL.drop_after.
- satellite: serving frontends hold-and-readmit through a rollback
  barrier (the engine-loss hold path, reused).
- THE acceptance: 2 workers + sharded WAL PS under seeded SDC with one
  poisoned worker — >= 1 coordinator-triggered rollback, fault-free-
  corridor convergence, byte-identical chaos logs 3x, every reject
  nacked and absent from any WAL.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_ml_pytorch_tpu.coord.coordinator import (
    KIND_SHARD,
    KIND_WORKER,
    Coordinator,
    encode_join,
    encode_renew,
    encode_rollback_done,
    encode_snapshot_done,
)
from distributed_ml_pytorch_tpu.coord.member import FleetView
from distributed_ml_pytorch_tpu.utils.chaos import (
    ChaosLog,
    ChaosPlan,
    FaultyTransport,
    SDCRule,
)
from distributed_ml_pytorch_tpu.utils.health import (
    NACK_NONFINITE,
    NACK_NORM_OUTLIER,
    GradientAdmission,
)
from distributed_ml_pytorch_tpu.utils.messaging import (
    InProcessTransport,
    MessageCode,
    ReliableTransport,
)
from distributed_ml_pytorch_tpu.parallel.async_ps import ParameterServer

pytestmark = pytest.mark.health


# ---------------------------------------------------------------------------
# GradientAdmission
# ---------------------------------------------------------------------------

def test_admission_warmup_then_outlier_rejected():
    g = GradientAdmission(z_max=6.0, warmup=3)
    clean = np.full(64, 0.01, np.float32)
    # warmup: even a big jump is admitted while statistics build
    assert g.evaluate(1, clean) is None
    assert g.evaluate(1, clean * 50) is None
    assert g.evaluate(1, clean) is None
    # gate active: a 1000x norm explosion is rejected, stats untouched
    before = g.snapshot()[1]
    verdict = g.evaluate(1, clean * 1000)
    assert verdict is not None and verdict[0] == NACK_NORM_OUTLIER
    assert verdict[2] > 6.0
    assert g.snapshot()[1] == before  # rejected sample never folds in
    # ordinary traffic keeps flowing
    assert g.evaluate(1, clean) is None


def test_admission_nonfinite_always_rejected():
    g = GradientAdmission(warmup=100)  # z-gate never active
    bad_nan = np.full(16, 0.1, np.float32)
    bad_nan[7] = np.nan
    bad_inf = np.full(16, 0.1, np.float32)
    bad_inf[3] = np.inf
    for bad in (bad_nan, bad_inf):
        verdict = g.evaluate(2, bad)
        assert verdict is not None and verdict[0] == NACK_NONFINITE
    assert g.evaluate(2, np.full(16, 0.1, np.float32)) is None


def test_admission_stats_are_per_sender():
    g = GradientAdmission(z_max=6.0, warmup=2)
    for _ in range(4):
        assert g.evaluate(1, np.full(32, 0.001, np.float32)) is None
        assert g.evaluate(2, np.full(32, 10.0, np.float32)) is None
    # sender 2's normal magnitude is sender 1's outlier
    assert g.evaluate(1, np.full(32, 10.0, np.float32)) is not None
    assert g.evaluate(2, np.full(32, 10.0, np.float32)) is None


def test_admission_blind_spot_sign_flip_passes():
    """PINNED: a norm-preserving corruption (sign flip = gradient ascent)
    passes the gate — the documented blind spot the rollback watchdog
    exists for. If a change makes this fail, the gate grew a new signal:
    update DESIGN.md §16 and the health-scenario script deliberately."""
    g = GradientAdmission(z_max=6.0, warmup=2)
    clean = np.linspace(-0.1, 0.1, 64).astype(np.float32)
    for _ in range(4):
        assert g.evaluate(1, clean) is None
    assert g.evaluate(1, -clean) is None  # identical norm: invisible


def test_admission_winsorized_ewma_stops_boiling_frog():
    """A sender ramping its norms by just-under-z_max per push must not
    walk the gate up an exponential: winsorized statistics reject the
    ramp after at most a couple of admitted outliers."""
    g = GradientAdmission(z_max=6.0, warmup=3, sigma_floor=0.5)
    x = np.full(64, 0.01, np.float32)
    for _ in range(4):
        assert g.evaluate(1, x) is None
    admitted = 0
    for _ in range(6):  # x15 per push: z ~ 5.4 each vs the ORIGINAL mean
        x = x * 15.0
        if g.evaluate(1, x) is None:
            admitted += 1
    assert admitted <= 2, "the gate followed an exponential norm ramp"


# ---------------------------------------------------------------------------
# ParameterServer quarantine: never silent, never in the WAL
# ---------------------------------------------------------------------------

def _reliable_pair():
    world = InProcessTransport.create_world(2)
    server = ReliableTransport(world[0], ack_timeout=0.05,
                               ack_on_delivery=False)
    worker = ReliableTransport(world[1], ack_timeout=0.05)
    return server, worker


def test_quarantine_nacks_and_skips_accounting(tmp_path):
    server_t, worker_t = _reliable_pair()
    try:
        ps = ParameterServer(
            params=np.zeros(8, np.float32), transport=server_t,
            admission=GradientAdmission(z_max=6.0, warmup=1))
        good = np.full(8, 0.01, np.float32)
        bad = np.full(8, np.nan, np.float32)
        worker_t.send(MessageCode.GradientUpdate, good, dst=0)
        worker_t.send(MessageCode.GradientUpdate, bad, dst=0)
        for _ in range(2):
            msg = server_t.recv(timeout=2.0)
            ps._envelope = server_t.last_delivery
            ps.handle(*msg)
        ps.commit()
        assert ps.quarantined == 1 and ps.nacks_sent == 1
        assert ps.quarantined_by_sender == {1: 1}
        assert ps._apply_seq == 1  # the bad one never touched the clock
        assert ps.applied_by_sender == {1: 1}
        assert np.isfinite(ps.central).all()
        # the reject is EXPLICIT: the worker receives an UpdateNack
        nack = worker_t.recv(timeout=2.0)
        assert nack is not None and nack[1] == MessageCode.UpdateNack
        assert int(nack[2][0]) == NACK_NONFINITE
        assert np.isfinite(nack[2]).all()  # clamped for the wire
    finally:
        server_t.close()
        worker_t.close()


def test_quarantine_burst_never_reaches_wal_and_restores_clean(tmp_path):
    """THE satellite regression: a rejected update must never reach the
    WAL — otherwise a poisoned record would be replayed on EVERY restore.
    After a quarantine burst, a restore replays exactly the good updates
    (acked <= applied + quarantined) and zero poison."""
    ckpt = str(tmp_path / "ps")
    server_t, worker_t = _reliable_pair()
    try:
        ps = ParameterServer(
            params=np.zeros(8, np.float32), transport=server_t,
            ckpt_dir=ckpt, ckpt_every=0, wal=True, wal_group_n=2,
            admission=GradientAdmission(z_max=6.0, warmup=2))
        good = np.full(8, 0.01, np.float32)
        ps.save_checkpoint()  # a base generation to restore over
        sent = []
        for k in range(8):
            # alternate: good, nan, good, huge-outlier, ...
            if k % 2 == 0:
                payload = good
            elif k % 4 == 1:
                payload = np.full(8, np.nan, np.float32)
            else:
                payload = np.full(8, 1e6, np.float32)
            sent.append(payload)
            worker_t.send(MessageCode.GradientUpdate, payload, dst=0)
        for _ in range(8):
            msg = server_t.recv(timeout=2.0)
            ps._envelope = server_t.last_delivery
            ps.handle(*msg)
            ps.commit()  # fsync + release the deferred ack per frame
        assert worker_t.flush(timeout=5.0)
        assert ps.quarantined == 4 and ps.nacks_sent == 4
        acked = worker_t.acked_count(0, MessageCode.GradientUpdate)
        assert acked <= ps.applied_by_sender.get(1, 0) + ps.quarantined
        # the log on disk holds ONLY the good updates, all finite
        records, stats = ps.wal.replay()
        assert len(records) == 4
        assert all(np.isfinite(r.payload).all() for r in records)
        # a fresh life restores checkpoint + WAL: zero poison replayed
        ps2 = ParameterServer(
            params=np.zeros(8, np.float32), transport=None,
            ckpt_dir=ckpt, ckpt_every=0, wal=True)
        assert ps2.maybe_restore()
        assert ps2.replayed_updates == 4
        assert ps2._apply_seq == 4
        assert np.isfinite(ps2.central).all()
        np.testing.assert_allclose(ps2.central, good * 4, rtol=1e-6)
    finally:
        server_t.close()
        worker_t.close()


# ---------------------------------------------------------------------------
# rollback_restore + WAL.drop_after
# ---------------------------------------------------------------------------

def test_rollback_restore_caps_replay_and_drops_wal_tail(tmp_path):
    ckpt = str(tmp_path / "ps")
    ps = ParameterServer(params=np.zeros(4, np.float32), transport=None,
                         ckpt_dir=ckpt, ckpt_every=0, wal=True)
    one = np.ones(4, np.float32)
    for _ in range(3):
        ps.handle(1, MessageCode.GradientUpdate, one)
    ps.save_checkpoint()  # ckpt at seq 3; WAL truncated
    for _ in range(4):  # post-snapshot updates live only in the WAL
        ps.handle(2, MessageCode.GradientUpdate, one)
    ps.commit()
    assert ps._apply_seq == 7
    discarded = ps.rollback_restore(5)
    assert discarded == 2 and ps.rolled_back_updates == 2
    assert ps._apply_seq == 5
    np.testing.assert_allclose(ps.central, one * 5)
    assert ps.applied_by_sender == {1: 3, 2: 2}
    # the dropped tail must not resurrect on a later crash-restore
    records, _stats = ps.wal.replay()
    assert [r.seq for r in records] == [4, 5]
    ps2 = ParameterServer(params=np.zeros(4, np.float32), transport=None,
                          ckpt_dir=ckpt, ckpt_every=0, wal=True)
    assert ps2.maybe_restore()
    assert ps2._apply_seq == 5
    np.testing.assert_allclose(ps2.central, one * 5)


def test_rollback_restore_refuses_checkpoint_ahead_of_target(tmp_path):
    ckpt = str(tmp_path / "ps")
    ps = ParameterServer(params=np.zeros(4, np.float32), transport=None,
                         ckpt_dir=ckpt, ckpt_every=0, wal=True)
    for _ in range(6):
        ps.handle(1, MessageCode.GradientUpdate, np.ones(4, np.float32))
    ps.save_checkpoint()  # on-disk generation is at seq 6
    with pytest.raises(ValueError, match="BEHIND the on-disk checkpoint"):
        ps.rollback_restore(3)


# ---------------------------------------------------------------------------
# SDC chaos: bit-perfect on the wire, deterministic
# ---------------------------------------------------------------------------

def _sdc_run(plan, payloads):
    world = InProcessTransport.create_world(2)
    log = ChaosLog()
    hub = FaultyTransport(world[0], plan, log=log)
    worker = hub.sibling(world[1])
    r0 = ReliableTransport(hub, ack_timeout=0.05)
    r1 = ReliableTransport(worker, ack_timeout=0.05)
    got = []
    try:
        for p in payloads:
            r1.send(MessageCode.GradientUpdate, p, dst=0)
            msg = r0.recv(timeout=2.0)
            got.append(msg[2])
        stats = dict(r0.stats)
    finally:
        r0.close()
        r1.close()
    return got, log.lines(), stats


def test_sdc_survives_envelope_crc_and_replays_deterministically():
    plan = ChaosPlan(seed=11, sdc=[
        SDCRule(src=1, dst=0, code=int(MessageCode.GradientUpdate), p=1.0,
                kind="nan", after=1, until=3)])
    payloads = [np.arange(8, dtype=np.float32)] * 4
    got1, lines1, stats1 = _sdc_run(plan, payloads)
    got2, lines2, stats2 = _sdc_run(plan, payloads)
    # the corruption was invisible to the wire layer: nothing CRC-dropped,
    # every frame DELIVERED — poisoned exactly inside the scripted window
    assert stats1["crc_dropped"] == 0 and stats1["delivered"] == 4
    assert not np.isnan(got1[0]).any() and not np.isnan(got1[3]).any()
    assert np.isnan(got1[1]).any() and np.isnan(got1[2]).any()
    # byte-identical logs, identical corrupted bytes
    assert lines1 and lines1 == lines2
    for a, b in zip(got1, got2):
        np.testing.assert_array_equal(a, b)


def test_sdc_scale_preserves_protocol_head():
    plan = ChaosPlan(seed=5, sdc=[
        SDCRule(src=1, dst=0, code=int(MessageCode.ShardPush), p=1.0,
                kind="scale", factor=-4.0, skip=6)])
    head = np.asarray([1, 2, 3, 4, 5, 6], np.float32)
    body = np.full(10, 2.0, np.float32)
    got, lines, _stats = _sdc_run_plain(plan, MessageCode.ShardPush,
                                        np.concatenate([head, body]))
    np.testing.assert_array_equal(got[:6], head)  # stamps untouched
    np.testing.assert_allclose(got[6:], body * -4.0)
    assert "sdc-scale" in lines


def _sdc_run_plain(plan, code, payload):
    """Un-enveloped path: SDC applies directly to the plain frame."""
    world = InProcessTransport.create_world(2)
    log = ChaosLog()
    hub = FaultyTransport(world[0], plan, log=log)
    worker = hub.sibling(world[1])
    try:
        worker.send(code, payload, dst=0)
        msg = hub.recv(timeout=2.0)
        return msg[2], log.lines(), None
    finally:
        hub.close()
        worker.close()


# ---------------------------------------------------------------------------
# worker-side nack resync (pull + update hold)
# ---------------------------------------------------------------------------

def test_worker_resyncs_and_holds_updates_on_nack():
    from distributed_ml_pytorch_tpu.parallel.sharded_ps import (
        ShardedAsynchronous,
    )

    world = InProcessTransport.create_world(2)
    params = {"w": jnp.zeros(8, jnp.float32)}
    grads = {"w": jnp.full(8, 0.5, jnp.float32)}
    opt = ShardedAsynchronous(params, lr=0.1, n_push=100, n_pull=100,
                              transports=[world[1]])
    try:
        # drain the construction install at the server end
        assert world[0].recv(timeout=1.0)[1] == MessageCode.ParameterUpdate
        p1 = opt.step(params, grads)  # idx 0: cadence pull fires
        assert world[0].recv(timeout=1.0)[1] == MessageCode.ParameterRequest
        moved = np.asarray(p1["w"])
        assert not np.allclose(moved, 0.0)  # updates applying normally
        # the server nacks one push: the worker resyncs with a pull and
        # HOLDS update application until fresh params install
        world[0].send(MessageCode.UpdateNack,
                      np.asarray([1.0, 5.0, 9.0], np.float32), dst=1)
        deadline = time.monotonic() + 2
        while opt.listeners[0]._nacks_pending == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        p2 = opt.step(p1, grads)
        assert opt.nacks == 1
        # the resync pull arrives (a cadence push from step 0 may
        # interleave ahead of it — FIFO per sender, multiple senders)
        seen = []
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            msg = world[0].recv(timeout=0.5)
            if msg is None:
                break
            seen.append(msg[1])
            if msg[1] == MessageCode.ParameterRequest:
                break
        assert MessageCode.ParameterRequest in seen, seen
        assert opt.skipped_updates == 1  # held: grads not applied
        np.testing.assert_array_equal(np.asarray(p2["w"]), moved)
        # the server answers with fresh params; the install releases the
        # hold and training resumes the step after
        world[0].send(MessageCode.ParameterUpdate,
                      np.full(8, 7.0, np.float32), dst=1)
        deadline = time.monotonic() + 2
        while opt.listeners[0]._latest is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        p3 = opt.step(p2, grads)  # installs 7s, still held this step
        np.testing.assert_allclose(np.asarray(p3["w"]), 7.0)
        p4 = opt.step(p3, grads)  # updates flow again
        assert not np.allclose(np.asarray(p4["w"]), 7.0)
    finally:
        opt._flusher.stop()
        for listener in opt.listeners:
            listener.stop()
        for t in world.values():
            t.close()


def test_single_ps_worker_holds_updates_on_nack():
    """The single-server Asynchronous worker carries the SAME post-nack
    hold discipline as ShardedAsynchronous: grads derived from diverged
    params must not stomp the resync install (install, stomp, explode,
    nack, repeat — the resync could never converge)."""
    from distributed_ml_pytorch_tpu.parallel.async_ps import Asynchronous

    world = InProcessTransport.create_world(2)
    params = {"w": jnp.zeros(8, jnp.float32)}
    grads = {"w": jnp.full(8, 0.5, jnp.float32)}
    opt = Asynchronous(params, lr=0.1, n_push=100, n_pull=100,
                       transport=world[1])
    try:
        # drain the construction install at the server end
        assert world[0].recv(timeout=1.0)[1] == MessageCode.ParameterUpdate
        p1 = opt.step(params, grads)  # idx 0: cadence pull fires
        assert world[0].recv(timeout=1.0)[1] == MessageCode.ParameterRequest
        moved = np.asarray(p1["w"])
        assert not np.allclose(moved, 0.0)
        # the server nacks one push: the worker resyncs AND holds
        world[0].send(MessageCode.UpdateNack,
                      np.asarray([1.0, 5.0, 9.0], np.float32), dst=1)
        deadline = time.monotonic() + 2
        while opt.listener._nacks_pending == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        p2 = opt.step(p1, grads)
        assert opt.nacks == 1
        assert opt.skipped_updates == 1  # held: grads not applied
        np.testing.assert_array_equal(np.asarray(p2["w"]), moved)
        # the resync pull went out
        seen = []
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            msg = world[0].recv(timeout=0.5)
            if msg is None:
                break
            seen.append(msg[1])
            if msg[1] == MessageCode.ParameterRequest:
                break
        assert MessageCode.ParameterRequest in seen, seen
        # fresh params release the hold; updates resume the step after
        world[0].send(MessageCode.ParameterUpdate,
                      np.full(8, 7.0, np.float32), dst=1)
        deadline = time.monotonic() + 2
        while opt.listener._latest is None and time.monotonic() < deadline:
            time.sleep(0.01)
        p3 = opt.step(p2, grads)  # installs 7s, still held this step
        np.testing.assert_allclose(np.asarray(p3["w"]), 7.0)
        p4 = opt.step(p3, grads)  # updates flow again
        assert not np.allclose(np.asarray(p4["w"]), 7.0)
    finally:
        opt._flusher.stop()
        opt.listener.stop()
        for t in world.values():
            t.close()


# ---------------------------------------------------------------------------
# coordinator: reputation + rollback watchdog (fake clock, no transport)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _renew(c, rank, inc, **kw):
    c.handle(rank, MessageCode.LeaseRenew, encode_renew(inc, **kw))


def test_reputation_revokes_and_cools_down():
    clk = _Clock()
    c = Coordinator(None, 100, lease=10.0, speculation=False, clock=clk,
                    reputation_nacks=3, reputation_cooldown=5.0)
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 7))
    _renew(c, 2, 7, nacks=0, loss_ewma=1.0)  # base anchors at 0
    _renew(c, 2, 7, nacks=2, loss_ewma=1.0)
    assert 2 in c.members  # under the limit
    _renew(c, 2, 7, nacks=3, loss_ewma=1.0)
    assert 2 not in c.members and c.revoked_workers == 1
    assert any("REVOKED" in e for e in c.events)
    # joins are refused during the cooldown — even a NEWER incarnation
    clk.t = 2.0
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 8))
    assert 2 not in c.members
    # after the cooldown the rank rejoins; its offense counter re-anchors
    clk.t = 6.0
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 8))
    assert 2 in c.members
    _renew(c, 2, 8, nacks=4, loss_ewma=1.0)  # base = 4 now: no offense
    assert 2 in c.members


def test_same_life_rejoin_keeps_telemetry():
    """The periodic idempotent re-join must NOT reset accumulated member
    telemetry (the bug that made reputation offenses unaccumulable)."""
    clk = _Clock()
    c = Coordinator(None, 100, lease=10.0, speculation=False, clock=clk)
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 7))
    _renew(c, 2, 7, nacks=5, wire_open=2, loss_ewma=3.0)
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 7))  # re-join
    m = c.members[2]
    assert m.nacks == 5 and m.wire_open == 2 and m.loss_ewma == 3.0
    # a genuinely NEW life does reset
    c.handle(2, MessageCode.CoordJoin, encode_join(KIND_WORKER, 9))
    assert c.members[2].nacks == 0


def _coord_with_manifest(tmp_path, clk, **kw):
    """A coordinator + one shard member + one snapshot barrier completed:
    the rollback watchdog's precondition (a good manifest) without any
    transport."""
    c = Coordinator(None, 100, lease=100.0, speculation=False, clock=clk,
                    manifest_dir=str(tmp_path), auto_rollback=True,
                    rollback_cooldown=50.0, rollback_timeout=20.0, **kw)
    c.handle(1, MessageCode.CoordJoin, encode_join(KIND_SHARD, 3))
    c.handle(4, MessageCode.CoordJoin, encode_join(KIND_WORKER, 5))
    mv = c.shard_map.version
    c.trigger_snapshot()
    c.tick()
    c.handle(1, MessageCode.SnapshotDone,
             encode_snapshot_done(1, mv, 0, 100, 12, 12))
    assert c.last_manifest is not None and c.last_manifest.complete
    return c, mv


def test_watchdog_rolls_back_on_loss_divergence_and_measures_mttr(tmp_path):
    clk = _Clock()
    c, mv = _coord_with_manifest(tmp_path, clk, rollback_loss_factor=1.5)
    _renew(c, 4, 5, loss_ewma=2.0)  # establish the best
    clk.t = 1.0
    c.tick()
    assert c._roll is None
    _renew(c, 4, 5, loss_ewma=3.5)  # > 1.5x best
    clk.t = 2.0
    c.tick()
    assert c._roll is not None
    assert any("ROLLBACK 1 started" in e for e in c.events)
    clk.t = 2.5
    c.handle(1, MessageCode.RollbackDone,
             encode_rollback_done(1, mv, 0, 100, 12))
    assert c.rollbacks_done == 1 and c._roll is None
    assert c.rollback_mttrs and abs(c.rollback_mttrs[0] - 0.5) < 1e-6
    # the cooldown + consumed evidence prevent an immediate re-fire
    _renew(c, 4, 5, loss_ewma=3.5)
    clk.t = 3.0
    c.tick()
    assert c._roll is None


def test_watchdog_rolls_back_on_nonfinite_loss_reports(tmp_path):
    clk = _Clock()
    c, mv = _coord_with_manifest(tmp_path, clk)
    _renew(c, 4, 5, loss_ewma=2.0)
    clk.t = 1.0
    c.tick()
    _renew(c, 4, 5, loss_ewma=2.0, bad_loss=1)
    clk.t = 2.0
    c.tick()
    assert c._roll is not None
    assert any("nonfinite" in e for e in c.events)
    # complete the barrier; a REBORN worker's bad_loss counter restarts,
    # so the consumed-evidence high-water mark must re-anchor with the
    # new life — its first nonfinite report is fresh evidence, not an echo
    c.handle(1, MessageCode.RollbackDone,
             encode_rollback_done(1, mv, 0, 100, 12))
    assert c.rollbacks_done == 1
    clk.t = 60.0  # past rollback_cooldown
    c.handle(4, MessageCode.CoordJoin, encode_join(KIND_WORKER, 9))
    _renew(c, 4, 9, loss_ewma=2.0, bad_loss=1)
    c.tick()
    assert c._roll is not None, "rebirth bad_loss suppressed by stale mark"


def test_rollback_barrier_times_out_and_releases(tmp_path):
    clk = _Clock()
    c, _mv = _coord_with_manifest(tmp_path, clk, rollback_loss_factor=1.5)
    c.trigger_rollback()
    clk.t = 1.0
    c.tick()
    assert c._roll is not None
    clk.t = 30.0  # past rollback_timeout: abandoned, completion broadcast
    c.tick()
    assert c._roll is None and c.rollbacks_abandoned == 1
    assert any("ABANDONED" in e for e in c.events)


def test_rollback_refused_without_matching_manifest(tmp_path):
    clk = _Clock()
    c = Coordinator(None, 100, lease=100.0, speculation=False, clock=clk,
                    auto_rollback=True)
    c.handle(4, MessageCode.CoordJoin, encode_join(KIND_WORKER, 5))
    c.trigger_rollback()
    c.tick()
    assert c._roll is None
    assert any("no FleetManifest" in e for e in c.events)


# ---------------------------------------------------------------------------
# satellite: serving frontend holds submits through a rollback barrier
# ---------------------------------------------------------------------------

def test_fleet_view_rollback_hold_and_ttl_fail_open():
    fleet = FleetView()
    assert not fleet.rollback_active()
    fleet.note_rollback(True, ttl=0.15)
    assert fleet.rollback_active()
    fleet.note_rollback(False)
    assert not fleet.rollback_active()
    # a LOST completion broadcast fails open after the TTL
    fleet.note_rollback(True, ttl=0.1)
    time.sleep(0.15)
    assert not fleet.rollback_active()


SERVE_VOCAB = 64


def test_frontend_holds_submits_through_rollback_barrier():
    from distributed_ml_pytorch_tpu.models.generate import generate
    from distributed_ml_pytorch_tpu.models.transformer import TransformerLM
    from distributed_ml_pytorch_tpu.serving.engine import ServingEngine
    from distributed_ml_pytorch_tpu.serving.frontend import (
        ServingClient,
        ServingFrontend,
    )

    model = TransformerLM(vocab_size=SERVE_VOCAB, d_model=32, n_heads=4,
                          n_layers=2, d_ff=64, max_len=128)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    engine = ServingEngine(model, params, slots=2, cache_size=64,
                           decode_block=4, prefill_bucket=8)
    world = InProcessTransport.create_world(2)
    fleet = FleetView()
    fleet.update({"version": 1, "n_workers": 1, "n_shards": 1,
                  "n_engines": 1, "workers_done": False})  # engines UP
    fleet.note_rollback(True, ttl=30.0)  # ... but a rollback is in flight
    frontend = ServingFrontend(engine, world[0], fleet=fleet)
    thread = threading.Thread(target=frontend.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(world[1], resume_after=0.25)
        prompt = np.random.default_rng(0).integers(0, SERVE_VOCAB, size=5)
        rid = client.submit(prompt, 8)
        deadline = time.monotonic() + 5
        while not frontend._held and time.monotonic() < deadline:
            time.sleep(0.01)
        with frontend._held_lock:
            assert len(frontend._held) == 1  # held, not submitted/rejected
        assert not frontend._routes
        # barrier completes: the sweep re-admits and the stream finishes
        fleet.note_rollback(False)
        tokens = list(client.stream(rid, timeout=60.0))
        want = np.asarray(
            generate(model, params, jnp.asarray(prompt, jnp.int32)[None], 8)
        )[0, 5:].tolist()
        assert tokens == want
        assert not frontend._held and frontend.held_peak == 1
    finally:
        frontend.stop()
        thread.join(timeout=10)
        for t in world.values():
            t.close()


# ---------------------------------------------------------------------------
# THE acceptance: seeded SDC + poisoned worker -> rollback -> corridor, 3x
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def health_fixture():
    from distributed_ml_pytorch_tpu.data import load_cifar10
    from distributed_ml_pytorch_tpu.models import LeNet
    from distributed_ml_pytorch_tpu.training.trainer import (
        cross_entropy_loss,
    )

    model = LeNet()
    x, y, *_ = load_cifar10(n_train=256, n_test=32, synthetic=True)

    @jax.jit
    def grad_fn(p, bx, by, rng):
        def loss_fn(q):
            logits = model.apply({"params": q}, bx, train=True,
                                 rngs={"dropout": rng})
            return cross_entropy_loss(logits, by)

        return jax.value_and_grad(loss_fn)(p)

    params0 = model.init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
    return x, y, grad_fn, params0


def test_immune_system_acceptance_three_runs(health_fixture, tmp_path,
                                             lock_witness):
    """THE acceptance (ISSUE 8), 3x with identical seeds: 2 workers +
    sharded WAL PS under seeded SDC, worker 2's push channel poisoned
    (norm-preserving-enough scale SDC that slips the gate, then NaN SDC
    the gate quarantines). Each run: >= 1 coordinator-triggered rollback,
    the poisoned worker revoked by reputation, every rejected update
    explicitly nacked and absent from any WAL, byte-identical chaos logs,
    and convergence into the fault-free corridor."""
    from distributed_ml_pytorch_tpu.coord.health import health_scenario

    clean = health_scenario(base_dir=str(tmp_path / "clean"), seed=7,
                            poison_worker=None, fixture=health_fixture)
    assert clean["ok"], (clean["errors"], clean["events"])
    assert clean["rollbacks"] == 0 and clean["quarantined_total"] == 0
    clean_final = np.mean(
        [np.mean(l[-4:]) for l in clean["losses"].values()])

    logs, finals = [], []
    for run in range(3):
        out = health_scenario(base_dir=str(tmp_path / f"run{run}"), seed=7,
                              fixture=health_fixture)
        assert out["ok"], (out["errors"], out["events"])
        # >= 1 coordinator-triggered rollback, completed and timed
        assert out["rollbacks"] >= 1, out["events"]
        assert out["rollback_mttr_s"] is not None \
            and out["rollback_mttr_s"] < 30
        assert all(n >= 1 for n in out["worker_rollbacks"].values())
        # the gate quarantined the NaN phase and every reject was nacked
        assert out["quarantined_total"] > 0
        assert out["nacks_explicit"], "a quarantine without its nack"
        assert out["worker_nacks"][2] > 0  # the poisoned worker heard them
        # reputation: the repeat offender lost its lease
        assert out["revoked_workers"] >= 1, out["events"]
        assert any("REVOKED" in e for e in out["events"])
        # sequence accounting closes (acked <= applied + quarantined +
        # rolled-back) and no server's params ever went nonfinite
        assert out["accounting_ok"], (out["acked"], out["applied"])
        assert out["central_finite"]
        # nothing quarantined ever reached a WAL: whatever survives on
        # disk replays finite (the unit test proves the ordering; this is
        # the end-to-end sweep)
        for i in range(2):
            wal_path = str(tmp_path / f"run{run}" / f"shard{i}"
                           / "ps_wal.log")
            if os.path.exists(wal_path):
                from distributed_ml_pytorch_tpu.utils.wal import replay_wal

                records, _stats = replay_wal(wal_path)
                assert all(np.isfinite(r.payload).all() for r in records)
        logs.append(out["chaos_lines"])
        for losses in out["losses"].values():
            assert np.mean(losses[-4:]) < np.mean(losses[:4]), losses
        finals.append(np.mean(
            [np.mean(l[-4:]) for l in out["losses"].values()]))
        print(f"DBG run{run}: final={finals[-1]:.4f} "
              f"rollbacks={out['rollbacks']} quar={out['quarantined_total']} "
              f"revoked={out['revoked_workers']} "
              f"worker_nacks={out['worker_nacks']}")
        for j, l in sorted(out["losses"].items()):
            print(f"DBG run{run} w{j}:",
                  [round(float(np.mean(np.asarray(l)[k:k+4])), 3)
                   for k in range(0, len(l), 4)])
        print(f"DBG run{run} events:", out["events"])
    assert logs[0] and logs[0] == logs[1] == logs[2], (
        "chaos log not byte-identical across runs")
    for final in finals:
        assert abs(final - clean_final) < 0.9, (final, clean_final)
