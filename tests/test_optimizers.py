"""Optimizer registry: weight decay and gradient clipping semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_ml_pytorch_tpu.training.trainer import make_optimizer


def _one_update(tx, grads, params):
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    return updates


def test_grad_clip_bounds_update_norm():
    params = {"w": jnp.zeros((4,))}
    grads = {"w": jnp.full((4,), 100.0)}  # global norm 200
    tx = make_optimizer("sgd", lr=1.0, grad_clip=1.0)
    upd = _one_update(tx, grads, params)
    norm = float(jnp.linalg.norm(upd["w"]))
    assert norm == pytest.approx(1.0, rel=1e-5)  # lr 1.0 × clipped norm 1.0


def test_grad_clip_leaves_small_gradients_alone():
    params = {"w": jnp.zeros((2,))}
    grads = {"w": jnp.asarray([0.3, 0.4])}  # norm 0.5 < 1.0
    tx = make_optimizer("sgd", lr=1.0, grad_clip=1.0)
    upd = _one_update(tx, grads, params)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.3, -0.4], rtol=1e-6)


def test_sgd_weight_decay_is_l2():
    """With zero gradients, the update must be -lr * wd * param."""
    params = {"w": jnp.asarray([2.0, -4.0])}
    grads = {"w": jnp.zeros((2,))}
    tx = make_optimizer("sgd", lr=0.1, weight_decay=0.01)
    upd = _one_update(tx, grads, params)
    np.testing.assert_allclose(
        np.asarray(upd["w"]), [-0.1 * 0.01 * 2.0, -0.1 * 0.01 * -4.0], rtol=1e-5
    )


def test_adamw_decay_is_decoupled():
    """adamw with wd must match optax.adamw exactly (decoupled decay, not
    gradient L2)."""
    params = {"w": jnp.asarray([2.0, -4.0])}
    grads = {"w": jnp.asarray([0.5, 0.5])}
    got = _one_update(make_optimizer("adamw", lr=0.1, weight_decay=0.01), grads, params)
    want = _one_update(optax.adamw(0.1, weight_decay=0.01), grads, params)
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(want["w"]), rtol=1e-6)


def test_adamw_default_keeps_optax_decay():
    """Unset weight_decay must preserve adamw's own default (1e-4), so the
    adamw/adam distinction survives the new knob."""
    params = {"w": jnp.asarray([2.0, -4.0])}
    grads = {"w": jnp.asarray([0.5, 0.5])}
    got = _one_update(make_optimizer("adamw", lr=0.1), grads, params)
    want = _one_update(optax.adamw(0.1), grads, params)
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(want["w"]), rtol=1e-6)
    plain_adam = _one_update(optax.adam(0.1), grads, params)
    assert not np.allclose(np.asarray(got["w"]), np.asarray(plain_adam["w"]))


def test_no_knobs_returns_bare_optimizer():
    """Default path must stay the reference recipe: plain sgd, no chain."""
    params = {"w": jnp.asarray([1.0])}
    grads = {"w": jnp.asarray([3.0])}
    got = _one_update(make_optimizer("sgd", lr=0.008), grads, params)
    np.testing.assert_allclose(np.asarray(got["w"]), [-0.008 * 3.0], rtol=1e-6)


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer("rmsprop", lr=0.1)
