"""M6 parity: packaging (the reference Makefile installs a missing setup.py as
``pytorch-distbelief``, Makefile:4,29,38)."""

import re

from setuptools import find_packages, setup

with open("distributed_ml_pytorch_tpu/version.py") as f:
    VERSION = re.search(r'__version__ = "([^"]+)"', f.read()).group(1)

setup(
    name="tpu-distbelief",
    version=VERSION,
    description=(
        "TPU-native distributed training framework with DownPour-SGD "
        "parameter-server, sync data-parallel, and local-SGD strategies"
    ),
    packages=find_packages(include=["distributed_ml_pytorch_tpu*"]),
    # ship the native transport source so installs can build it on demand
    # (native/__init__.py ensure_built compiles with the local g++)
    package_data={"distributed_ml_pytorch_tpu.native": ["transport.cpp", "Makefile"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
        "pandas",
        # default runtime paths use these: per-epoch classification report
        # (trainer.evaluate verbose) and the graph plotter
        "scikit-learn",
        "matplotlib",
        # checkpoint/resume subsystem (utils/checkpoint.py)
        "orbax-checkpoint",
    ],
    extras_require={
        "dev": ["pytest"],
    },
)
