"""One-command real-data verification (VERDICT r2 #6) — self-closing.

Every accuracy claim in BASELINE.md was measured on the deterministic
synthetic CIFAR-10 stand-in because this sandbox has no network egress; the
download path itself is implemented and tested against a fabricated archive
(``data/cifar10.py``, ``tests/test_data.py``). This script is the one
command that closes the gap the moment egress exists:

    make verify-real-data        (or: python verify_real_data.py)

It downloads the genuine dataset via the framework's own
``download_cifar10`` (md5-verified, atomic install), runs ONE
steps-to-target pass of both frameworks on the identical real batch stream
(``bench_all.bench_steps_to_accuracy``), derives every reported crossing
from the recorded accuracy curves, and appends the outcome to
``BASELINE.md`` under a "Real-data verification" heading plus a JSON line
on stdout. Without egress it prints SKIP and exits 0, so CI can run it
unconditionally.

Reported, all honestly:
- steps to 99% (the synthetic north-star bar — real CIFAR-10 will cap-hit
  at this recipe; the cap-hit is recorded as the measured outcome),
- steps to 60% (reachable at the reference recipe's horizon, so the
  cross-framework step comparison is informative on real data), and
- the FINAL accuracies of both frameworks after the full 2000-step stream
  — the parity delta the north-star acceptance bar asks about.
"""

from __future__ import annotations

import datetime
import json
import os
import sys


def _first_crossing(curve, eval_every, target):
    for i, acc in enumerate(curve):
        if acc >= target:
            return (i + 1) * eval_every
    return None


def main() -> int:
    from distributed_ml_pytorch_tpu.data import load_cifar10

    from distributed_ml_pytorch_tpu.data.cifar10 import (
        CIFAR10_MD5, CIFAR10_URL, _TARBALL)

    drop_path = os.path.abspath(os.path.join("./data", _TARBALL))
    try:
        x, _y, _xt, _yt, is_synth = load_cifar10(
            root="./data", synthetic=False, download=True)
    except Exception as e:
        print(
            f"SKIP: real CIFAR-10 unavailable ({type(e).__name__}: {e}) — "
            "no network egress here.\n"
            "To close the bar WITHOUT egress, drop the canonical tarball "
            "where the loader already looks (it is picked up, md5-verified, "
            "and used on the next run — no code change needed):\n"
            f"  file : {_TARBALL}\n"
            f"  from : {CIFAR10_URL}\n"
            f"  md5  : {CIFAR10_MD5}\n"
            f"  to   : {drop_path}\n"
            "then re-run:  make verify-real-data",
            file=sys.stderr)
        print(json.dumps({"metric": "real_data_verification",
                          "status": "skipped_no_egress",
                          "drop_file_to_close": drop_path,
                          "expected_md5": CIFAR10_MD5}))
        return 0
    assert not is_synth and len(x) == 50000

    from bench_all import bench_steps_to_accuracy, log

    # one pass, both frameworks, full 2000-step stream; every target's
    # crossing derives from the recorded curves
    (_js, _ts, torch_status, jax_acc, torch_acc, curves) = (
        bench_steps_to_accuracy(target=0.60, synthetic=False))
    ee = curves["eval_every"]
    results = {
        "jax_steps_to_99": _first_crossing(curves["jax"], ee, 0.99),
        "jax_steps_to_60": _first_crossing(curves["jax"], ee, 0.60),
        "torch_steps_to_99": _first_crossing(curves["torch"], ee, 0.99),
        "torch_steps_to_60": _first_crossing(curves["torch"], ee, 0.60),
        "torch_status": torch_status,
        "jax_final_acc": jax_acc,
        "torch_final_acc": torch_acc,
    }
    delta = (abs(jax_acc - torch_acc) if torch_acc is not None else None)
    results["final_acc_delta"] = delta
    rec = {"metric": "real_data_verification", "status": "measured", **results}
    print(json.dumps(rec))

    stamp = datetime.datetime.now().strftime("%Y-%m-%d")
    t_final = (f"{torch_acc:.4f}" if torch_acc is not None
               else f"unavailable ({torch_status})")
    d_final = f"{delta:.4f}" if delta is not None else "n/a"
    row = (f"| real CIFAR-10 ({stamp}) | jax→99%: "
           f"{results['jax_steps_to_99'] or 'cap'} steps, jax→60%: "
           f"{results['jax_steps_to_60'] or 'cap'}, torch→60%: "
           f"{results['torch_steps_to_60'] or 'cap'} | final acc "
           f"jax {jax_acc:.4f} vs torch {t_final} (Δ {d_final}) | "
           "identical 2000-step batch stream, reference recipe |\n")
    header = "## Real-data verification (appended by verify_real_data.py)\n"
    existing = ""
    if os.path.exists("BASELINE.md"):
        with open("BASELINE.md", encoding="utf-8") as fh:
            existing = fh.read()
    with open("BASELINE.md", "a", encoding="utf-8") as fh:
        if header not in existing:
            fh.write("\n" + header + "\n| run | steps-to-target | parity | "
                     "boundary |\n|---|---|---|---|\n")
        fh.write(row)
    log("appended real-data verification row to BASELINE.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
